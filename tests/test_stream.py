"""Streaming telemetry bus (ISSUE 20): typed streams, bounded
drop-oldest subscriber queues, producer-keyed cursor resume, and the
``/watch`` + ``/watch/info`` + ``/debug/profile/diff`` transport.

Unit tests drive a :class:`TelemetryBus` over injected fake sources
(deterministic seqs, no threads); endpoint tests reuse the live debug
server from the continuous-profiling plane and certify the tentpole's
resume contract end-to-end: reconnect with cursors delivers every
missed event exactly once — no duplicates, no full re-bootstrap.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from janusgraph_tpu.observability import (
    flight_recorder,
    history,
    registry,
    sampling_profiler,
    slo_engine,
    telemetry_bus,
    watchdog,
)
from janusgraph_tpu.observability.continuous import watchdog_singleton
from janusgraph_tpu.observability.stream import STREAMS, TelemetryBus


# ------------------------------------------------------------ fake sources
class _FakeRecorder:
    def __init__(self):
        self._listeners = []
        self._events = []
        self.last_seq = 0

    def add_listener(self, fn):
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def events(self):
        return [dict(e) for e in self._events]

    def record(self, category, **fields):
        self.last_seq += 1
        ev = {
            "seq": self.last_seq, "ts": float(self.last_seq),
            "category": category, **fields,
        }
        self._events.append(ev)
        for fn in list(self._listeners):
            fn(ev)
        return ev


class _FakeHistory:
    def __init__(self):
        self._listeners = []
        self._windows = []

    def add_listener(self, fn):
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def last_seq(self):
        return self._windows[-1]["seq"] if self._windows else 0

    def windows(self, last=0):
        return [dict(w) for w in self._windows]

    def seal(self, counters=None, series=None, gauges=None):
        w = {
            "seq": len(self._windows) + 1, "ts": 0.0,
            "counters": counters or {}, "series": series or {},
            "gauges": gauges or {},
        }
        self._windows.append(w)
        for fn in list(self._listeners):
            fn(w)
        return w


class _FakeProfiler:
    def __init__(self):
        self._listeners = []
        self._windows = []
        self._seal_seq = 0

    def add_seal_listener(self, fn):
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_seal_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def last_seal_seq(self):
        return self._seal_seq

    def windows(self, last=0):
        return [dict(w) for w in self._windows]

    def seal(self, seq):
        w = {"seq": seq, "ts": 0.0, "samples": 1, "stacks": {}}
        if seq > 0:
            self._seal_seq = seq
            self._windows.append(w)
        for fn in list(self._listeners):
            fn(w)
        return w


def _bus(depth=256):
    rec, hist, prof = _FakeRecorder(), _FakeHistory(), _FakeProfiler()
    bus = TelemetryBus(
        depth=depth, history=hist, recorder=rec, profiler=prof
    )
    return bus, rec, hist, prof


# --------------------------------------------------------------- unit: bus
class TestBus:
    def test_taxonomy_and_unknown_stream_rejected(self):
        assert STREAMS == ("flight", "window", "slo", "flame", "bundle")
        bus, _rec, _hist, _prof = _bus()
        with pytest.raises(ValueError, match="unknown streams"):
            bus.subscribe(streams=["flight", "metrics"])

    def test_publish_fans_out_typed_envelopes(self):
        bus, rec, hist, _prof = _bus()
        flights = bus.subscribe(streams=["flight"], name="f")
        windows = bus.subscribe(streams=["window"], name="w")
        rec.record("compaction", action="start")
        hist.seal(counters={"app.ops": 3})
        ev = flights.pop()
        assert ev == {
            "stream": "flight", "seq": 1,
            "data": {"seq": 1, "ts": 1.0, "category": "compaction",
                     "action": "start"},
        }
        assert flights.pop(timeout=0) is None  # no window leakage
        w = windows.pop()
        assert w["stream"] == "window" and w["seq"] == 1
        assert w["data"]["counters"] == {"app.ops": 3}
        assert bus.subscriber_count() == 2
        for sub in (flights, windows):
            bus.unsubscribe(sub)

    def test_derived_streams_share_the_flight_seq(self):
        """slo/bundle are flight-derived: same ring, same seqs — one
        cursor vocabulary across the whole flight family."""
        bus, rec, _hist, _prof = _bus()
        sub = bus.subscribe(streams=["flight", "slo", "bundle"], name="d")
        rec.record("slo_burn", slo="availability")
        rec.record("bundle", reason="stall")
        got = [(e["stream"], e["seq"]) for e in sub.drain()]
        assert got == [
            ("flight", 1), ("slo", 1), ("flight", 2), ("bundle", 2),
        ]
        bus.unsubscribe(sub)

    def test_flame_fallback_seal_is_not_streamed(self):
        """A seal with no aligned history window (seq <= 0) never hits
        the flame stream — its seq is meaningless as a cursor."""
        bus, _rec, _hist, prof = _bus()
        sub = bus.subscribe(streams=["flame"], name="fl")
        prof.seal(-1)
        assert sub.pop(timeout=0) is None
        prof.seal(7)
        assert sub.pop()["seq"] == 7
        bus.unsubscribe(sub)

    def test_drop_oldest_accounting(self):
        """A slow consumer costs ITSELF data — never the producer: the
        oldest event drops, the counter records it (JG113 contract)."""
        bus, rec, _hist, _prof = _bus()
        dropped0 = registry.get_count("observability.stream.dropped")
        sub = bus.subscribe(streams=["flight"], depth=4, name="slow")
        for i in range(10):
            rec.record("tick", n=i)
        assert sub.dropped == 6
        assert bus.dropped == 6
        assert [e["seq"] for e in sub.drain()] == [7, 8, 9, 10]
        assert registry.get_count(
            "observability.stream.dropped"
        ) == dropped0 + 6
        stats = sub.stats()
        assert stats["enqueued"] == 10 and stats["dropped"] == 6
        bus.unsubscribe(sub)

    def test_cursor_resume_replays_retained_tail_exactly_once(self):
        """THE tentpole contract: a cursor is a replay floor — the
        retained tail past it replays, live events append, and the
        seam between them never duplicates or loses a seq."""
        bus, rec, _hist, _prof = _bus()
        for i in range(5):
            rec.record("tick", n=i)
        sub = bus.subscribe(
            streams=["flight"], cursors={"flight": 2}, name="resume"
        )
        rec.record("tick", n=5)  # live, behind the replay
        assert [e["seq"] for e in sub.drain()] == [3, 4, 5, 6]
        # replay+live race: a re-publish of a replayed seq is a no-op
        assert bus.publish("flight", 4, {"seq": 4}) == 0
        assert sub.drain() == []
        bus.unsubscribe(sub)

    def test_no_cursor_means_live_only(self):
        bus, rec, _hist, _prof = _bus()
        rec.record("old")
        sub = bus.subscribe(streams=["flight"], name="live")
        assert sub.pop(timeout=0) is None  # history NOT re-bootstrapped
        rec.record("new")
        assert sub.pop()["data"]["category"] == "new"
        bus.unsubscribe(sub)

    def test_bus_cursors_read_the_sources(self):
        bus, rec, hist, prof = _bus()
        rec.record("a")
        rec.record("b")
        hist.seal()
        prof.seal(1)
        assert bus.cursors() == {
            "flight": 2, "window": 1, "slo": 2, "flame": 1, "bundle": 2,
        }

    def test_name_filters_trim_windows_and_gate_flight(self):
        """Category-prefix filtering: flight-family events gate on
        category, windows are trimmed to matching metric names.  The
        cursor still advances past filtered events — a filtered stream
        is NOT gap-free, by design."""
        bus, rec, hist, _prof = _bus()
        sub = bus.subscribe(
            streams=["flight", "window"], names=("compaction",),
            name="filt",
        )
        rec.record("gc", pause_ms=3)
        rec.record("compaction", level=1)
        hist.seal(counters={"compaction.bytes": 9, "gc.pauses": 1})
        hist.seal(counters={"gc.pauses": 2})
        got = sub.drain()
        assert [(e["stream"], e["seq"]) for e in got] == [
            ("flight", 2), ("window", 1),
        ]
        assert got[1]["data"]["counters"] == {"compaction.bytes": 9}
        # filtered events still advanced the cursor (gap by design)
        assert sub.stats()["cursors"] == {"flight": 2, "window": 2}
        bus.unsubscribe(sub)

    def test_subscriber_drain_is_a_watchdog_progress_source(self):
        """Satellite 1: every subscriber auto-registers its drain with
        the watchdog singleton — a queue holding events whose delivered
        count froze is a wedged consumer, caught with no wiring."""
        bus, rec, _hist, _prof = _bus()
        sub = bus.subscribe(streams=["flight"], name="drainee")
        wd = watchdog_singleton()
        assert "stream.drainee" in wd._progress
        assert sub._progress() == {"active": 0, "progress": 0}
        rec.record("tick")
        assert sub._progress()["active"] == 1  # queued, undelivered
        sub.pop()
        assert sub._progress() == {"active": 0, "progress": 1}
        bus.unsubscribe(sub)
        assert "stream.drainee" not in wd._progress

    def test_publish_self_cost_on_both_clocks(self):
        bus, rec, _hist, _prof = _bus()
        sub = bus.subscribe(streams=["flight"], name="clk")
        rec.record("tick")
        status = bus.status()
        assert status["published"] == 1
        assert status["overhead_wall_ms"] >= 0.0
        assert status["overhead_cpu_ms"] >= 0.0
        _c, _t, _h, gauges = registry.metric_objects()
        assert "observability.stream.overhead_wall_ms" in gauges
        assert "observability.stream.overhead_cpu_ms" in gauges
        bus.unsubscribe(sub)

    def test_configure_depth_and_reset(self):
        bus, rec, _hist, _prof = _bus()
        bus.configure(depth=8)
        sub = bus.subscribe(streams=["flight"], name="cfg")
        assert sub.depth == 8
        rec.record("tick")
        bus.reset()
        assert sub.closed
        assert bus.subscriber_count() == 0
        assert bus.status()["published"] == 0
        assert "stream.cfg" not in watchdog_singleton()._progress


# --------------------------------------------------- endpoints: /watch
@pytest.fixture
def watch_server(tmp_path):
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    for step in (
        sampling_profiler.stop, sampling_profiler.reset,
        watchdog.stop, watchdog.reset,
        flight_recorder.reset, registry.reset,
    ):
        step()
    telemetry_bus.reset()
    g = open_graph({"ids.authority-wait-ms": 0.0})
    m = JanusGraphManager()
    m.put_graph("graph", g)
    s = JanusGraphServer(manager=m, bundle_dir=str(tmp_path)).start()
    yield s
    s.stop()
    g.close()
    telemetry_bus.reset()
    history.reset()
    slo_engine.reset()
    for step in (
        sampling_profiler.stop, sampling_profiler.reset,
        watchdog.stop, watchdog.reset,
        flight_recorder.reset, registry.reset,
    ):
        step()
    import janusgraph_tpu.server.server as server_mod

    with server_mod._HEALTH_LOCK:
        server_mod._HEALTH_STATE["status"] = None


def _get(base, path):
    return urllib.request.urlopen(base + path, timeout=5).read()


def _session(port, subscribe):
    from janusgraph_tpu.driver.client import WatchSession

    return WatchSession("127.0.0.1:%d" % port, subscribe=subscribe)


def _recv_events(session, n, timeout=5.0):
    """Collect the next n event frames, skipping heartbeats."""
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n:
        assert time.monotonic() < deadline, f"got {out}, wanted {n}"
        frame = session.recv(timeout=0.25)
        if frame and frame.get("type") == "event":
            out.append(frame)
    return out


class TestWatchEndpoint:
    def test_watch_info_advertises_capability_and_cursors(
        self, watch_server
    ):
        base = "http://127.0.0.1:%d" % watch_server.port
        info = json.loads(_get(base, "/watch/info"))
        assert info["watch"] is True
        assert info["streams"] == list(STREAMS)
        assert set(info["cursors"]) == set(STREAMS)
        assert info["subscribers"] == 0
        assert isinstance(info["now"], float)

    def test_live_events_then_cursor_resume_exactly_once(
        self, watch_server
    ):
        """The acceptance path over a real socket: subscribe, see live
        flight events, disconnect mid-stream, reconnect with the last
        seen cursor — every missed event arrives exactly once."""
        base = "http://127.0.0.1:%d" % watch_server.port
        s1 = _session(
            watch_server.port,
            {"streams": ["flight"], "name": "t-live"},
        )
        try:
            hello = s1.recv(timeout=5.0)
            assert hello["type"] == "hello"
            assert set(hello["cursors"]) == set(STREAMS)
            flight_recorder.record("compaction", action="start", n=1)
            (ev,) = _recv_events(s1, 1)
            assert ev["stream"] == "flight"
            assert ev["data"]["category"] == "compaction"
            last = ev["seq"]
        finally:
            s1.close()
        # events missed while disconnected...
        flight_recorder.record("compaction", action="mid", n=2)
        flight_recorder.record("compaction", action="end", n=3)
        info = json.loads(_get(base, "/watch/info"))
        assert info["cursors"]["flight"] == last + 2
        s2 = _session(
            watch_server.port,
            {"streams": ["flight"], "cursors": {"flight": last},
             "name": "t-resume"},
        )
        try:
            evs = _recv_events(s2, 2)
            assert [e["seq"] for e in evs] == [last + 1, last + 2]
            assert [e["data"]["action"] for e in evs] == ["mid", "end"]
            # exactly once: no event frame remains queued
            tail = s2.recv(timeout=0.3)
            assert tail is None or tail.get("type") != "event"
        finally:
            s2.close()

    def test_heartbeats_carry_drop_count_and_bad_subscribe_errors(
        self, watch_server
    ):
        s = _session(
            watch_server.port,
            {"streams": ["flight"], "heartbeat_s": 0.01, "name": "t-hb"},
        )
        try:
            # the cadence clamps to >= 0.2 s; an idle stream heartbeats
            hello = s.recv(timeout=5.0)
            assert hello["type"] == "hello"
            assert hello["heartbeat_s"] == 0.2
            deadline = time.monotonic() + 5.0
            frame = None
            while frame is None or frame.get("type") != "heartbeat":
                assert time.monotonic() < deadline
                frame = s.recv(timeout=0.5)
            assert frame["dropped"] == 0
            assert isinstance(frame["ts"], float)
        finally:
            s.close()
        bad = _session(watch_server.port, {"streams": ["bogus"]})
        try:
            frame = bad.recv(timeout=5.0)
            assert frame["type"] == "error"
            assert "unknown streams" in frame["message"]
        finally:
            bad.close()


class TestProfileDiffEndpoint:
    def test_diff_serves_frame_deltas_between_sealed_windows(
        self, watch_server
    ):
        base = "http://127.0.0.1:%d" % watch_server.port
        deadline = time.monotonic() + 5.0
        while sampling_profiler.status()["samples"] < 3:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        sampling_profiler.seal_window()
        sampling_profiler.sample_once()
        sampling_profiler.seal_window()
        body = json.loads(_get(base, "/debug/profile/diff"))
        # defaults: a=-2, b=-1 — the last two retained windows
        assert set(body) == {"a", "b", "frames"}
        for side in ("a", "b"):
            assert set(body[side]) == {"seq", "ts", "samples"}
        assert isinstance(body["frames"], list)
        if body["frames"]:
            row = body["frames"][0]
            assert {"frame", "old_us", "new_us", "delta_us",
                    "delta_pct"} <= set(row)
        top = json.loads(_get(base, "/debug/profile/diff?top=1"))
        assert len(top["frames"]) <= 1

    def test_diff_404_names_the_retained_windows(self, watch_server):
        base = "http://127.0.0.1:%d" % watch_server.port
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base, "/debug/profile/diff?a=99999")
        assert ei.value.code == 404
        body = json.loads(ei.value.read())
        assert "retained" in body["status"]["message"]


class TestWatchCLI:
    def test_watch_cli_tails_n_events_and_exits(
        self, watch_server, capsys
    ):
        from janusgraph_tpu.cli import main

        def _pump():
            # feed events until the tail below has consumed one
            for i in range(50):
                flight_recorder.record("cli-probe", n=i)
                time.sleep(0.05)

        t = threading.Thread(target=_pump, daemon=True)
        t.start()
        rc = main([
            "watch", "--url", "127.0.0.1:%d" % watch_server.port,
            "--streams", "flight", "--names", "cli-probe",
            "--count", "2",
        ])
        t.join(timeout=10.0)
        assert rc == 0
        out = capsys.readouterr().out
        lines = [l for l in out.splitlines() if "cli-probe" in l]
        assert len(lines) == 2
        assert "flight" in lines[0]
