"""Sharded (mesh) executor tests on the 8-virtual-device CPU mesh — the
"multi-node without a cluster" harness (SURVEY.md §4). Parity against the
scalar CPU oracle is the acceptance gate for the distributed path.
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges, run_on
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
    PeerPressureProgram,
    ShortestPathProgram,
    TraversalCountProgram,
)
from janusgraph_tpu.parallel import ShardedExecutor


def random_graph(n=170, m=700, seed=11, weights=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0.5, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    assert len(devices) == 8, "conftest must provide 8 virtual devices"
    return Mesh(devices, ("p",))


PROGRAMS = [
    ("pagerank", lambda: PageRankProgram(max_iterations=25)),
    ("sssp", lambda: ShortestPathProgram(seed_index=0)),
    ("sssp_weighted", lambda: ShortestPathProgram(seed_index=3, weighted=True)),
    ("cc", lambda: ConnectedComponentsProgram()),
    ("khop", lambda: TraversalCountProgram(hops=3)),
    ("peer_pressure", lambda: PeerPressureProgram(num_buckets=512)),
]


@pytest.mark.parametrize("name,make", PROGRAMS, ids=[p[0] for p in PROGRAMS])
def test_sharded_matches_cpu_oracle(mesh8, name, make):
    g = random_graph(weights=True)
    cpu = run_on(g, make(), "cpu")
    sharded = ShardedExecutor(g, mesh=mesh8).run(make())
    assert set(cpu) == set(sharded)
    for k in cpu:
        got = np.asarray(sharded[k], dtype=np.float64)
        assert got.shape[0] == g.num_vertices  # padding stripped
        np.testing.assert_allclose(
            got, cpu[k], rtol=1e-4, atol=1e-5, err_msg=f"{name}:{k}"
        )


def test_sharded_pagerank_mass_conserved(mesh8):
    g = random_graph(n=333, m=1200)  # deliberately not divisible by 8
    res = ShardedExecutor(g, mesh=mesh8).run(PageRankProgram(max_iterations=30))
    assert abs(res["rank"].sum() - 1.0) < 1e-4


def test_sharded_tiny_graph_fewer_vertices_than_shards(mesh8):
    g = csr_from_edges(3, [0, 1], [1, 2])
    res = ShardedExecutor(g, mesh=mesh8).run(ShortestPathProgram(seed_index=0))
    np.testing.assert_allclose(res["distance"], [0, 1, 2])


@pytest.mark.parametrize("exchange,agg", [
    ("a2a", "ell"), ("a2a", "segment"), ("gather", "segment"),
    ("ring", "segment"),
])
def test_exchange_agg_matrix_parity(mesh8, exchange, agg):
    """Every exchange × aggregation configuration gives oracle results."""
    g = random_graph(n=190, m=900, seed=5, weights=True)
    for make in (
        lambda: PageRankProgram(max_iterations=12),
        lambda: ShortestPathProgram(seed_index=2, weighted=True),
    ):
        cpu = run_on(g, make(), "cpu")
        ex = ShardedExecutor(g, mesh=mesh8, exchange=exchange, agg=agg)
        res = ex.run(make())
        for k in cpu:
            np.testing.assert_allclose(
                np.asarray(res[k], np.float64), cpu[k], rtol=1e-4, atol=1e-5,
                err_msg=f"{exchange}/{agg}:{k}",
            )


def test_a2a_comm_volume_proportional_to_boundary(mesh8):
    """The all-to-all exchange moves only boundary buckets: its per-shard
    volume (S*B elements) is bounded by the distinct cross-shard sources,
    not by the O(n) vertex count the all_gather path moves (VERDICT r1
    weakness #3)."""
    # a strongly local graph: each vertex only links to near neighbours, so
    # only the ~k vertices at each shard edge are boundary sources
    n, k = 4096, 4
    src = np.repeat(np.arange(n, dtype=np.int64), k)
    dst = (src + np.tile(np.arange(1, k + 1), n)) % n
    g = csr_from_edges(n, src.astype(np.int32), dst.astype(np.int32))
    ex = ShardedExecutor(g, mesh=mesh8)
    stats = ex.comm_stats()
    assert stats["gather_elems"] == 4096
    # boundary per (q->s) pair is at most k distinct sources
    assert stats["boundary_width"] <= k
    assert stats["a2a_elems"] <= 8 * k  # S * B
    # and the result is still exact
    cpu = run_on(g, ShortestPathProgram(seed_index=0), "cpu")
    res = ex.run(ShortestPathProgram(seed_index=0))
    np.testing.assert_allclose(res["distance"], cpu["distance"])


def test_supernode_row_split_parity(mesh8, monkeypatch):
    """Degrees beyond the ELL capacity row-split instead of padding a jumbo
    bucket to the max degree; results stay exact."""
    import janusgraph_tpu.parallel.sharded as sharded_mod

    monkeypatch.setattr(sharded_mod, "_ELL_MAX_CAPACITY", 8)
    rng = np.random.default_rng(3)
    n = 120
    # hub vertex 7 receives edges from everyone (in-degree ~n >> capacity 8)
    src = np.concatenate([
        np.arange(n), rng.integers(0, n, 300)
    ]).astype(np.int32)
    dst = np.concatenate([
        np.full(n, 7), rng.integers(0, n, 300)
    ]).astype(np.int32)
    g = csr_from_edges(n, src, dst)
    ex = sharded_mod.ShardedExecutor(g, mesh=mesh8)
    sc = ex._sharded(False)
    sc.ensure_ell()
    assert any(m is not None for m in sc.ell_meta), "expected a split bucket"
    for make in (
        lambda: PageRankProgram(max_iterations=15),
        lambda: ShortestPathProgram(seed_index=0),
    ):
        cpu = run_on(g, make(), "cpu")
        res = ex.run(make())
        for k in cpu:
            np.testing.assert_allclose(
                np.asarray(res[k], np.float64), cpu[k], rtol=1e-4, atol=1e-5
            )


def test_sharded_single_device_mesh():
    import jax
    from jax.sharding import Mesh

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("p",))
    g = random_graph(n=50, m=200)
    cpu = run_on(g, PageRankProgram(max_iterations=15), "cpu")
    res = ShardedExecutor(g, mesh=mesh1).run(PageRankProgram(max_iterations=15))
    np.testing.assert_allclose(res["rank"], cpu["rank"], rtol=1e-4, atol=1e-6)


def test_ring_exchange_parity_all_programs(mesh8):
    """The ring (ppermute-streamed blocks, the ring-attention pattern)
    matches the oracle for every monoid/program shape, including fused
    spans (while_loop + ppermute in the loop body)."""
    g = random_graph(n=190, m=800, seed=13, weights=True)
    for name, make in PROGRAMS:
        cpu = run_on(g, make(), "cpu")
        ex = ShardedExecutor(g, mesh=mesh8, exchange="ring", agg="segment")
        res = ex.run(make())
        for k in cpu:
            np.testing.assert_allclose(
                np.asarray(res[k], np.float64), cpu[k], rtol=1e-4, atol=1e-5,
                err_msg=f"ring:{name}:{k}",
            )


def test_ring_comm_stats(mesh8):
    g = random_graph(n=512, m=2000)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="ring", agg="segment")
    stats = ex.comm_stats()
    # S-1 = 7 hops of one shard-block each; own block never leaves the chip
    assert stats["ring_elems"] == 7 * stats["ring_peak_elems"]
    assert stats["a2a_elems"] is None  # the a2a plan is not materialized


# ---------------------------------------------------------------- frontier
# Per-shard frontier compaction (parallel/sharded_frontier.py): parity with
# the dense sharded path at every step cutoff — the per-step-identical
# claim, verified where it can actually fail (mid-run frontiers).


def test_sharded_frontier_dense_parity_at_step_cutoffs(mesh8):
    g = random_graph(n=300, m=1500, seed=7)
    ex = ShardedExecutor(g, mesh=mesh8)
    seed = int(np.argmax(g.out_degree))
    for k in (1, 2, 3, 5):
        prog = ShortestPathProgram(seed_index=seed, max_iterations=k)
        front = ex.run(prog)
        assert ex.last_run_info["path"] == "frontier"
        dense = ex.run(prog, frontier="off")
        np.testing.assert_array_equal(
            front["distance"], dense["distance"], err_msg=f"cutoff {k}"
        )


def test_sharded_frontier_weighted_and_paths(mesh8):
    g = random_graph(n=200, m=900, seed=3, weights=True)
    ex = ShardedExecutor(g, mesh=mesh8)
    pw = ShortestPathProgram(seed_index=1, weighted=True, max_iterations=12)
    np.testing.assert_allclose(
        ex.run(pw)["distance"], ex.run(pw, frontier="off")["distance"],
        rtol=1e-5,
    )
    pt = ShortestPathProgram(seed_index=1, max_iterations=6, track_paths=True)
    rf, rd = ex.run(pt), ex.run(pt, frontier="off")
    np.testing.assert_array_equal(rf["predecessor"], rd["predecessor"])
    np.testing.assert_array_equal(rf["distance"], rd["distance"])
    # predecessor chain-walk terminates at the seed (a real path exists)
    pred = rf["predecessor"].astype(np.int64)
    reached = np.nonzero(rf["distance"] < 1e17)[0]
    v = int(reached[-1])
    for _ in range(g.num_vertices):
        if v == 1:
            break
        v = int(pred[v])
    assert v == 1


def test_sharded_frontier_cc_parity_and_trace(mesh8):
    g = random_graph(n=260, m=1000, seed=5, weights=True)  # weights ignored
    ex = ShardedExecutor(g, mesh=mesh8)
    cc = ConnectedComponentsProgram(max_iterations=32)
    rf = ex.run(cc, frontier="always")
    assert ex.last_run_info["path"] == "frontier"
    tiers = ex.last_run_info["tiers"]
    assert tiers and all(
        t["edges"] >= 0 and t["F_cap"] >= t["shard_max_frontier"]
        for t in tiers
    )
    # the changed-frontier shrinks towards fixpoint
    assert tiers[-1]["frontier"] <= tiers[0]["frontier"]
    rd = ex.run(cc, frontier="off")
    np.testing.assert_array_equal(rf["component"], rd["component"])


def test_sharded_frontier_matches_cpu_oracle(mesh8):
    from janusgraph_tpu.olap import run_on

    g = random_graph(n=180, m=800, seed=13)
    seed = int(np.argmax(g.out_degree))
    prog = ShortestPathProgram(seed_index=seed)
    cpu = run_on(g, prog, "cpu")
    got = ShardedExecutor(g, mesh=mesh8).run(prog)
    np.testing.assert_allclose(got["distance"], cpu["distance"], rtol=1e-6)


def test_sharded_frontier_respects_off_and_checkpoint(mesh8, tmp_path):
    g = random_graph(n=150, m=600, seed=2)
    ex = ShardedExecutor(g, mesh=mesh8)
    prog = ShortestPathProgram(seed_index=0, max_iterations=4)
    ex.run(prog, frontier="off")
    assert ex.last_run_info.get("path") != "frontier"
    # checkpointing rides the dense path (frontier runs are short)
    ex.run(
        prog, checkpoint_path=str(tmp_path / "ck"), checkpoint_every=2,
    )
    assert ex.last_run_info.get("path") != "frontier"
