"""ISSUE 6 gate: degree-bucketed hybrid format + profiler-driven autotuner.

Three contracts:

1. **Determinism** — `autotune.decide` is a pure function: identical
   (GraphStats, device_kind, overrides, measured) give an identical
   AutotuneDecision, and stats built twice from the same CSR are equal.
2. **Bitwise identity** — the hybrid strategy's results are bit-for-bit
   equal to the pure-ELL path (PageRank/BFS/CC oracles, weighted and
   unweighted, supernode row-split, 2-D messages), on the device executor
   AND the CPU executor's numpy replay of the same pack arithmetic.
3. **Wiring** — the decision lands in `run_info["autotune"]`, the
   `computer.autotune-*` keys override it, and the frontier engine prices
   hops against the tuner's tier schedule.
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges, run_on
from janusgraph_tpu.olap.autotune import (
    AutotuneDecision,
    GraphStats,
    decide,
    decide_tiers,
    pick_tier,
)
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.kernels import (
    ELLPack,
    HybridPack,
    ell_aggregate,
    hybrid_aggregate,
    tree_reduce,
)
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
    ShortestPathProgram,
)
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.olap.vertex_program import Combiner, EdgeTransform


def skewed_graph(n=600, m=12000, seed=7, weights=False):
    """Heavy-tailed destinations: a torso plus genuine hubs."""
    rng = np.random.default_rng(seed)
    dst = (rng.zipf(1.35, m) % n).astype(np.int64)
    src = rng.integers(0, n, m).astype(np.int64)
    w = rng.uniform(0.25, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


# ----------------------------------------------------------- determinism
def test_decision_deterministic():
    csr = skewed_graph()
    s1 = GraphStats.from_csr(csr)
    s2 = GraphStats.from_csr(csr)
    assert s1 == s2
    d1 = decide(s1, "cpu")
    d2 = decide(s2, "cpu")
    assert d1 == d2
    assert isinstance(d1, AutotuneDecision)
    # overrides and measurements are part of the function's inputs: same
    # inputs, same decision — and they do change it
    ov = {"hub_cutoff": 32, "min_gain": 0.0}
    assert decide(s1, "cpu", overrides=ov) == decide(s2, "cpu", overrides=ov)
    meas = {"superstep_ms": 12.5, "pad_ratio": 1.47}
    dm1 = decide(s1, "cpu", measured=meas)
    dm2 = decide(s1, "cpu", measured=meas)
    assert dm1 == dm2
    assert dm1.source == "measured+model"


def test_decision_device_kind_sensitivity():
    """device_kind is a decision input: the record carries it, and the
    roofline peaks it selects are what the model prices against."""
    s = GraphStats.from_csr(skewed_graph())
    d_cpu = decide(s, "cpu")
    d_tpu = decide(s, "TPU v5e lite")
    assert d_cpu.device_kind != d_tpu.device_kind
    assert d_cpu == decide(s, "cpu")


def test_stats_shape():
    csr = skewed_graph()
    s = GraphStats.from_csr(csr)
    assert s.num_vertices == csr.num_vertices
    assert s.num_edges == csr.num_edges
    assert s.ell_slots >= s.num_edges
    # every candidate's hybrid footprint is at least the edge count and at
    # most the ELL footprint's worst case
    for _cutoff, slots, _hubs, _buckets, chunk_rows in s.hybrid_by_cutoff:
        assert slots >= s.num_edges - s.num_vertices  # deg-0 rows are free
        assert chunk_rows >= 0
    und = GraphStats.from_csr(csr, undirected=True)
    assert und.num_edges == 2 * csr.num_edges


def test_config_overrides_force_choice():
    s = GraphStats.from_csr(skewed_graph())
    forced = decide(s, "cpu", overrides={"strategy": "segment"})
    assert forced.strategy == "segment" and forced.source == "config"
    cut = decide(
        s, "cpu", overrides={"strategy": "hybrid", "hub_cutoff": 64}
    )
    assert cut.strategy == "hybrid" and cut.hub_cutoff == 64
    # a tiny budget pushes the auto choice off the packed layouts
    tiny = decide(s, "cpu", overrides={"budget_bytes": 1024})
    assert tiny.strategy == "segment"


def test_tier_schedules_pow2_and_bounded():
    s = GraphStats.from_csr(skewed_graph())
    f_sched, e_sched = decide_tiers(s, {"max_tiers": 4})
    for sched, hi in ((f_sched, s.num_vertices), (e_sched, s.num_edges)):
        assert len(sched) <= 4 + 1
        assert list(sched) == sorted(sched)
        for t in sched[:-1]:
            assert t & (t - 1) == 0, f"non-pow2 tier {t}"
    # pick_tier: smallest tier covering the need; top = dense fallback
    assert pick_tier(1, e_sched, s.num_edges) == e_sched[0]
    assert pick_tier(10 ** 9, e_sched, s.num_edges) == s.num_edges
    # measured refinement: a mid tier with ~zero utilization is pruned
    mid = e_sched[1] if len(e_sched) > 2 else None
    if mid is not None:
        _f2, e2 = decide_tiers(
            s, {"max_tiers": 4},
            measured={"roofline_by_tier": {
                str(mid): {"roofline_utilization": 0.0},
            }},
        )
        assert mid not in e2


# ------------------------------------------------- bitwise result identity
BITWISE_PROGRAMS = [
    ("pagerank", lambda: PageRankProgram(max_iterations=12, tol=0.0), "rank"),
    ("bfs", lambda: ShortestPathProgram(seed_index=3, max_iterations=6),
     "distance"),
    ("cc", lambda: ConnectedComponentsProgram(max_iterations=40),
     "component"),
]


@pytest.mark.parametrize("weights", [False, True], ids=["unweighted", "w"])
@pytest.mark.parametrize(
    "name,make,key", BITWISE_PROGRAMS, ids=[p[0] for p in BITWISE_PROGRAMS]
)
def test_hybrid_bitwise_equals_ell_device(name, make, key, weights):
    """The tentpole contract: hybrid and pure-ELL runs are bit-for-bit
    identical on the device executor (frontier off so the dense BSP path
    is what's compared)."""
    g = skewed_graph(weights=weights)
    ell = TPUExecutor(g, strategy="ell").run(make(), frontier="off")
    hyb = TPUExecutor(g, strategy="hybrid").run(make(), frontier="off")
    assert set(ell) == set(hyb)
    for k in ell:
        np.testing.assert_array_equal(
            np.asarray(hyb[k]), np.asarray(ell[k]),
            err_msg=f"device:{name}:{k}",
        )


@pytest.mark.parametrize(
    "name,make,key", BITWISE_PROGRAMS, ids=[p[0] for p in BITWISE_PROGRAMS]
)
def test_hybrid_bitwise_equals_ell_cpu(name, make, key):
    """Same contract on the CPU executor's numpy replay of the packs —
    and both pack strategies agree with the scalar oracle to float32
    tolerance."""
    g = skewed_graph(seed=11)
    oracle = CPUExecutor(g).run(make())
    ell = CPUExecutor(g, strategy="ell").run(make())
    hyb = CPUExecutor(g, strategy="hybrid").run(make())
    for k in oracle:
        np.testing.assert_array_equal(
            np.asarray(hyb[k]), np.asarray(ell[k]),
            err_msg=f"cpu:{name}:{k}",
        )
        np.testing.assert_allclose(
            np.asarray(ell[k], dtype=np.float64), oracle[k],
            rtol=1e-4, atol=1e-5, err_msg=f"cpu-oracle:{name}:{k}",
        )


def test_hybrid_bitwise_supernode_row_split():
    """Hubs past max_capacity row-split; the tail's chunked partial fold
    must reproduce the split rows' segment combine bit-for-bit."""
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    n, m = 300, 8000
    dst = np.concatenate([
        np.zeros(5000, dtype=np.int64),  # one monster hub
        (rng.zipf(1.4, m - 5000) % n).astype(np.int64),
    ])
    src = rng.integers(0, n, m)
    msgs = rng.uniform(-1, 1, n).astype(np.float32)
    ell = ELLPack(src, dst, None, n, max_capacity=64)
    hyb = HybridPack(
        src, dst, None, n, hub_cutoff=8, tail_chunk=16, max_capacity=64
    )
    for op in (Combiner.SUM, Combiner.MIN, Combiner.MAX):
        a = np.asarray(ell_aggregate(jnp, ell, jnp.asarray(msgs), op))
        b = np.asarray(hybrid_aggregate(jnp, hyb, jnp.asarray(msgs), op))
        np.testing.assert_array_equal(b, a, err_msg=op)


def test_hybrid_pad_ratio_beats_ell():
    """The point of the format: on a heavy-tailed graph the hybrid pack
    moves <1.15x the edge count where pow2 ELL moves ~1.5x."""
    g = skewed_graph(n=2000, m=40000)
    fp = TPUExecutor.ell_footprint(g)
    dst = np.repeat(
        np.arange(g.num_vertices, dtype=np.int64), np.diff(g.in_indptr)
    )
    hyb = HybridPack(g.in_src.astype(np.int64), dst, None, g.num_vertices)
    assert fp["pad_ratio"] > 1.3
    assert hyb.pad_ratio < 1.15
    assert hyb.pad_ratio < fp["pad_ratio"]


def test_tree_reduce_fixed_tree():
    """tree_reduce is the adjacent-pair tree: chunked evaluation of an
    aligned pow2 sub-range equals the sub-tree, the identity property the
    hybrid tail rests on. Non-pow2 widths are refused."""
    rng = np.random.default_rng(0)
    x = rng.uniform(0.001, 1.0, (3, 64)).astype(np.float32)
    whole = tree_reduce(np, x, Combiner.SUM)
    chunks = x.reshape(3, 4, 16)
    partial = np.stack(
        [tree_reduce(np, chunks[:, j], Combiner.SUM) for j in range(4)],
        axis=1,
    )
    np.testing.assert_array_equal(
        tree_reduce(np, partial, Combiner.SUM), whole
    )
    with pytest.raises(ValueError):
        tree_reduce(np, x[:, :60], Combiner.SUM)


# ----------------------------------------------------------------- wiring
def test_run_info_records_decision():
    g = skewed_graph()
    ex = TPUExecutor(g)
    ex.run(PageRankProgram(max_iterations=4, tol=0.0))
    rec = ex.last_run_info.get("autotune")
    assert rec is not None
    assert rec["strategy"] in ("ell", "hybrid", "segment")
    assert rec["source"] in ("model", "config", "measured+model")
    assert rec["e_schedule"] == sorted(rec["e_schedule"])
    assert ex.last_run_info["pad_ratio"] == ex.last_run_info["ell_pad_ratio"]
    # explicit strategies still record provenance
    ex2 = TPUExecutor(g, strategy="ell")
    ex2.run(PageRankProgram(max_iterations=4, tol=0.0))
    assert ex2.last_run_info["autotune"]["source"] == "config"
    assert ex2.last_run_info["strategy_resolved"] == "ell"


def test_frontier_uses_tuned_schedule():
    g = skewed_graph(n=3000, m=30000)
    ex = TPUExecutor(g)
    ex.run(ShortestPathProgram(seed_index=0, max_iterations=4))
    info = ex.last_run_info
    assert info["path"] == "frontier"
    sched = tuple(info["autotune"]["e_schedule"])
    for tier in info["tiers"]:
        assert tier["tier_source"] == "autotune"
        assert tier["E_cap"] in sched or tier["E_cap"] == g.num_edges
    # tuner off -> legacy ladder
    ex2 = TPUExecutor(g, autotune=False)
    ex2.run(ShortestPathProgram(seed_index=0, max_iterations=4))
    assert all(
        t["tier_source"] == "static" for t in ex2.last_run_info["tiers"]
    )


def test_computer_config_keys_flow_through():
    """graph.compute() forwards the computer.autotune-* keys."""
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({
        "storage.backend": "inmemory",
        "computer.autotune-hub-cutoff": 16,
        "computer.autotune-tail-chunk": 32,
        "computer.strategy": "hybrid",
    })
    tx = g.new_transaction()
    prev = None
    for _ in range(12):
        v = tx.add_vertex()
        if prev is not None:
            tx.add_edge(prev, "next", v)
        prev = v
    tx.commit()
    res = (
        g.compute(executor="tpu")
        .program(PageRankProgram(max_iterations=3, tol=0.0))
        .submit()
    )
    assert len(res.states["rank"]) == 12
    g.close()


def test_run_on_cpu_strategy_plumbs():
    g = skewed_graph(seed=4)
    scalar = run_on(g, PageRankProgram(max_iterations=5, tol=0.0), "cpu")
    hyb = run_on(
        g, PageRankProgram(max_iterations=5, tol=0.0), "cpu",
        cpu_strategy="hybrid",
    )
    np.testing.assert_allclose(
        hyb["rank"], scalar["rank"], rtol=1e-4, atol=1e-6
    )


def test_hybrid_2d_messages_and_transform_bitwise():
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    n, m, k = 120, 2400, 4
    dst = (rng.zipf(1.5, m) % n).astype(np.int64)
    src = rng.integers(0, n, m)
    w = rng.uniform(0.1, 3.0, m).astype(np.float32)
    msgs = rng.uniform(0, 1, (n, k)).astype(np.float32)
    ell = ELLPack(src, dst, w, n)
    hyb = HybridPack(src, dst, w, n, hub_cutoff=8, tail_chunk=8)
    for tr in (EdgeTransform.MUL_WEIGHT, EdgeTransform.ADD_WEIGHT):
        a = np.asarray(
            ell_aggregate(jnp, ell, jnp.asarray(msgs), Combiner.SUM, tr)
        )
        b = np.asarray(
            hybrid_aggregate(jnp, hyb, jnp.asarray(msgs), Combiner.SUM, tr)
        )
        np.testing.assert_array_equal(b, a, err_msg=tr)


def test_hybrid_pack_rejects_bad_shapes():
    g = skewed_graph(seed=3)
    dst = np.repeat(
        np.arange(g.num_vertices, dtype=np.int64), np.diff(g.in_indptr)
    )
    with pytest.raises(ValueError):
        HybridPack(
            g.in_src.astype(np.int64), dst, None, g.num_vertices,
            tail_chunk=100,
        )
    with pytest.raises(ValueError):
        HybridPack(
            g.in_src.astype(np.int64), dst, None, g.num_vertices,
            hub_cutoff=0,
        )
